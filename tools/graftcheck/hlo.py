"""Shared compiled-HLO parsing/counting core.

ONE parser, two front-ends: ``tools/hlo_census`` (the per-split
dispatch budget over the grow while-bodies, PR 8) and
``tools/graftcheck`` (the per-program contract checker over every
registered jit entry point). The census helpers here are moved
verbatim from the original ``tools/hlo_census.py`` — the committed
budget and the reported fixed-config counts depend on their exact
counting rules, so any change here must keep
``tools/hlo_census_budget.json`` green without --update.

Everything operates on the textual form of a compiled module
(``jitted.lower(...).compile().as_text()``) — the only artifact both
jax 0.4 and newer releases expose stably.
"""

from __future__ import annotations

import re
from collections import Counter
from typing import Dict, Iterator, List, Tuple

# --- census counting rules (see tools/hlo_census.py header) -----------
TRIVIAL_OPS = ("get-tuple-element", "parameter", "constant", "tuple",
               "bitcast")
DTYPE_TOKENS = ("f32", "s32", "u32", "u8", "pred", "u16", "bf16", "s8",
                "s64", "f64", "u64", "c64", "c128", "s16", "f16")
COLLECTIVE_OPS = ("all-reduce", "all-gather", "reduce-scatter",
                  "collective-permute", "all-to-all")

DTYPE_BYTES = {"pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
               "bf16": 2, "f16": 2, "s32": 4, "u32": 4, "f32": 4,
               "s64": 8, "u64": 8, "f64": 8}

# 8-byte element types: the x64 family a silent widening pays double
# bandwidth for (c128 is 16 but never legitimate here either)
WIDE_DTYPES = ("f64", "s64", "u64", "c128")

# custom-call targets that round-trip through the host per dispatch
HOST_CALLBACK_MARKERS = ("callback", "outside_compilation", "host_")
# ops that ARE host round-trips regardless of target
HOST_TRANSFER_OPS = ("infeed", "outfeed", "send", "recv", "send-done",
                     "recv-done")
# dynamic-shape machinery (bounded dynamism / padded programs)
DYNAMIC_SHAPE_OPS = ("set-dimension-size", "get-dimension-size",
                     "dynamic-reshape")
DYNAMIC_CALL_MARKERS = ("PadToStatic", "SliceToDynamic")


def op_of(line: str):
    """HLO opcode of one instruction line (first known-op token
    preceding a paren that is not a dtype)."""
    rhs = line.split(" = ", 1)[1]
    for cand in re.findall(r"([a-z][a-z0-9\-]*)\(", rhs):
        if cand not in DTYPE_TOKENS:
            return cand
    return None


def shape_bytes(shape: str) -> int:
    m = re.match(r"([a-z0-9]+)\[([\d,]*)\]", shape)
    if not m:
        return 0
    n = 1
    for d in m.group(2).split(","):
        if d:
            n *= int(d)
    return n * DTYPE_BYTES.get(m.group(1), 4)


def carry_stats(line: str) -> Tuple[int, int]:
    """(elements, bytes) of a while instruction's carry tuple."""
    m = re.search(r"= \((.*?)\) while\(", line)
    if not m:
        return 0, 0
    shapes = re.findall(r"[a-z0-9]+\[[\d,]*\](?:\{[\d,]*\})?",
                        m.group(1))
    return len(shapes), sum(shape_bytes(s) for s in shapes)


_CALLED_RE = re.compile(
    r"(?:body|condition|calls|to_apply|true_computation|"
    r"false_computation)=(%[\w.\-]+)")
_BRANCHES_RE = re.compile(r"branch_computations=\{([^}]*)\}")


def _computation_graph(txt: str):
    """(ops per computation, computations referenced per computation):
    the call graph the grow-while selection walks."""
    lines = txt.splitlines()
    comps: Dict[str, Counter] = {}
    refs: Dict[str, set] = {}
    name = None
    for ln in lines:
        stripped = ln.strip()
        if stripped.endswith("{") and "(" in stripped:
            head = stripped.split("(", 1)[0].strip()
            if head.startswith("ENTRY "):
                head = head[len("ENTRY "):].strip()
            name = head.split()[-1] if head else name
            comps.setdefault(name, Counter())
            refs.setdefault(name, set())
            continue
        if name is None or " = " not in ln:
            continue
        op = op_of(ln)
        if op:
            comps[name][op] += 1
        for m in _CALLED_RE.finditer(ln):
            refs[name].add(m.group(1))
        for m in _BRANCHES_RE.finditer(ln):
            refs[name].update(re.findall(r"%[\w.\-]+", m.group(1)))
    return comps, refs


def census_from_hlo(txt: str) -> dict:
    """Census of the grow while loop inside one compiled HLO module.

    The grow while is the ``while`` op WITHOUT a ``known_trip_count``
    backend_config (scatter expansions and pallas grid loops are
    trip-counted) whose body TRANSITIVELY holds the most non-trivial
    ops — the outermost loop of the program, which always contains any
    nested dynamic loop (e.g. the megakernel's interpret-mode DMA
    streams). Reported counts are the body's DIRECT ops: non-trivial =
    everything except parameter / constant / tuple /
    get-tuple-element / bitcast; inner ``while`` ops count as ONE op
    each (on TPU they are one kernel)."""
    comps, refs = _computation_graph(txt)

    def nontrivial_of(counter: Counter) -> int:
        return sum(counter.values()) - sum(counter[t]
                                           for t in TRIVIAL_OPS)

    trans_cache: Dict[str, int] = {}

    def trans_ops(name: str, stack=()):
        if name in trans_cache:
            return trans_cache[name]
        if name not in comps or name in stack:
            return 0
        total = nontrivial_of(comps[name])
        for r in refs.get(name, ()):
            total += trans_ops(r, stack + (name,))
        trans_cache[name] = total
        return total

    candidates = []  # (body_name, carry_elems, carry_bytes)
    for m in re.finditer(r"body=(%[\w.\-]+)", txt):
        s = txt.rfind("\n", 0, m.start()) + 1
        line = txt[s:txt.find("\n", m.end())]
        if "known_trip_count" in line:
            continue
        elems, nbytes = carry_stats(line)
        candidates.append((m.group(1), elems, nbytes))
    best = None
    best_trans = -1
    for body, elems, nbytes in candidates:
        if body not in comps:
            continue
        ops = comps[body]
        total = sum(ops.values())
        tr = trans_ops(body)
        if best is None or tr > best_trans:
            best_trans = tr
            best = dict(
                body=body.lstrip("%"),
                ops_per_split=nontrivial_of(ops),
                total_instructions=total,
                fusions=ops.get("fusion", 0),
                inner_whiles=ops.get("while", 0),
                collectives=sum(ops.get(c, 0) for c in COLLECTIVE_OPS),
                carry_arrays=elems,
                carry_bytes=nbytes,
                op_histogram={k: v for k, v in sorted(
                    ops.items(), key=lambda kv: -kv[1])},
            )
    if best is None:
        raise RuntimeError("no grow while loop found in compiled HLO")
    return best


# --- whole-module views (the graftcheck front-end) --------------------
def iter_instructions(txt: str) -> Iterator[Tuple[int, str, str, str]]:
    """Yield (1-based line, computation name, opcode, line text) for
    every instruction in the module. The computation name tracks the
    enclosing ``%name ... {`` block (``ENTRY`` blocks report their
    entry name)."""
    comp = ""
    for i, ln in enumerate(txt.splitlines(), start=1):
        stripped = ln.strip()
        if stripped.endswith("{") and ("(" in stripped):
            head = stripped.split("(", 1)[0].strip()
            if head.startswith("ENTRY "):
                head = head[len("ENTRY "):].strip()
            comp = head.split()[-1].lstrip("%") if head else comp
            continue
        if " = " not in ln:
            continue
        op = op_of(ln)
        if op:
            yield i, comp, op, ln


def module_op_counts(txt: str) -> Counter:
    """Non-bookkeeping opcode counts across the module, EXCLUDING the
    bodies of fusion computations (a fusion is one dispatch; its inner
    element ops are already paid for by the ``fusion`` op itself)."""
    ops: Counter = Counter()
    for _line, comp, op, _txt in iter_instructions(txt):
        if "fused_computation" in comp:
            continue
        ops[op] += 1
    return ops


def nontrivial_total(ops: Counter) -> int:
    return sum(ops.values()) - sum(ops[t] for t in TRIVIAL_OPS)


def collective_census(txt: str) -> Dict[str, int]:
    """Exact multiset of collective ops in the module (fusion bodies
    excluded — collectives never fuse)."""
    ops = module_op_counts(txt)
    return {c: ops[c] for c in COLLECTIVE_OPS if ops.get(c)}


def result_dtype(line: str) -> str:
    """Element type of an instruction's result shape ('' when the
    result is a tuple or unparsable)."""
    rhs = line.split(" = ", 1)[1].lstrip()
    m = re.match(r"([a-z0-9]+)\[", rhs)
    return m.group(1) if m and m.group(1) in DTYPE_TOKENS else ""


def wide_dtype_lines(txt: str) -> List[Tuple[int, str]]:
    """Instructions producing 8-byte-element results (f64/s64/u64/c128)
    — the dtype-discipline violations GC2xx reports. ``constant`` ops
    are exempt: XLA embeds s64 scalar constants for machinery (e.g.
    callback target pointers) that never touches the compute path — a
    REAL f64 leak always surfaces in the converts/arithmetic too. An
    f64 parameter still counts: it means an f64 input crossed the jit
    boundary."""
    out = []
    for i, _comp, op, ln in iter_instructions(txt):
        if op == "constant":
            continue
        dt = result_dtype(ln)
        if dt in WIDE_DTYPES:
            out.append((i, ln.strip()))
    return out


def widening_convert_lines(txt: str) -> List[Tuple[int, str]]:
    """``convert`` instructions whose result element type is one of the
    8-byte x64 family and whose operand is narrower — the classic
    python-float / np-scalar promotion leak."""
    out = []
    for i, _comp, op, ln in iter_instructions(txt):
        if op != "convert":
            continue
        dst = result_dtype(ln)
        if dst not in WIDE_DTYPES:
            continue
        m = re.search(r"convert\(([a-z0-9]+)\[", ln)
        src = m.group(1) if m else ""
        if src and DTYPE_BYTES.get(src, 4) < DTYPE_BYTES.get(dst, 8):
            out.append((i, ln.strip()))
    return out


def host_callback_lines(txt: str) -> List[Tuple[int, str]]:
    """Host round-trips compiled into the program: python callbacks
    (``custom-call`` whose target mentions a callback), infeed/outfeed
    and host send/recv ops."""
    out = []
    for i, _comp, op, ln in iter_instructions(txt):
        if op in HOST_TRANSFER_OPS:
            out.append((i, ln.strip()))
            continue
        if op == "custom-call":
            m = re.search(r'custom_call_target="([^"]+)"', ln)
            tgt = m.group(1) if m else ""
            if any(k in tgt for k in HOST_CALLBACK_MARKERS):
                out.append((i, ln.strip()))
    return out


def dynamic_shape_lines(txt: str) -> List[Tuple[int, str]]:
    """Dynamic-shape machinery: bounded-dynamic result shapes
    (``f32[<=128]``), set/get-dimension-size, dynamic-reshape, and the
    PadToStatic/SliceToDynamic custom calls."""
    out = []
    for i, _comp, op, ln in iter_instructions(txt):
        if op in DYNAMIC_SHAPE_OPS:
            out.append((i, ln.strip()))
            continue
        if op == "custom-call":
            m = re.search(r'custom_call_target="([^"]+)"', ln)
            if m and any(k in m.group(1)
                         for k in DYNAMIC_CALL_MARKERS):
                out.append((i, ln.strip()))
                continue
        rhs = ln.split(" = ", 1)[1].lstrip()
        if re.match(r"[a-z0-9]+\[[^\]]*<=", rhs):
            out.append((i, ln.strip()))
    return out


_ALIAS_RE = re.compile(r"input_output_alias=\{")


def alias_entries(txt: str) -> List[Tuple[str, int]]:
    """Parse the module header's ``input_output_alias`` map into
    (output index tuple text, aliased parameter number) pairs. An
    empty list means NO donation materialized."""
    m = _ALIAS_RE.search(txt)
    if not m:
        return []
    depth = 1
    i = m.end()
    while i < len(txt) and depth:
        if txt[i] == "{":
            depth += 1
        elif txt[i] == "}":
            depth -= 1
        i += 1
    block = txt[m.end():i - 1]
    return [(out_idx, int(param))
            for out_idx, param in re.findall(
                r"\{([\d,\s]*)\}:\s*\((\d+)", block)]


def aliased_param_count(txt: str) -> int:
    """Number of DISTINCT input parameters aliased to outputs — the
    materialized-donation count the GC1xx contract checks."""
    return len({p for _o, p in alias_entries(txt)})
