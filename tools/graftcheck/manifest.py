"""The committed contract manifest (``tools/graftcheck/contracts.json``).

Same workflow as ``tools/hlo_census_budget.json``: ``--check`` compares
the current lowered artifacts against the committed measurements +
slack, ``--update`` rewrites the measurements while PRESERVING the
human-owned fields (``ops_slack``, ``fusions_slack``, ``allow``,
``note``). Collective multisets and donation counts are exact — no
slack: a new all-reduce or a dropped alias is never benign drift.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List

from .findings import GcFinding

MANIFEST_PATH = os.path.join(os.path.dirname(__file__),
                             "contracts.json")

# human-owned per-program fields --update must never clobber
PRESERVED_FIELDS = ("ops_slack", "fusions_slack", "allow", "note")


def load_manifest(path: str = MANIFEST_PATH) -> Dict:
    if not os.path.exists(path):
        return {"config": {}, "programs": {}}
    with open(path) as f:
        return json.load(f)


def default_slacks(ops: int, fusions: int) -> Dict[str, int]:
    """First-update slack: 10% rounded up, floored at 8 ops / 4
    fusions (the hlo_census defaults scaled to whole-module counts)."""
    return {"ops_slack": max(8, (ops + 9) // 10),
            "fusions_slack": max(4, (fusions + 9) // 10)}


def update_manifest(current: Dict, path: str = MANIFEST_PATH) -> Dict:
    """Merge a census run (``{"config": ..., "programs": {name:
    measurements}}``) into the committed manifest, preserving
    human-owned fields, and write it. Programs missing from this run
    are kept untouched (a partial --programs update must not drop
    them)."""
    manifest = load_manifest(path)
    progs = manifest.setdefault("programs", {})
    for name, cur in current["programs"].items():
        entry = progs.setdefault(name, {})
        kept = {k: entry[k] for k in PRESERVED_FIELDS if k in entry}
        entry.clear()
        entry.update(cur)
        for k, v in default_slacks(cur["ops"], cur["fusions"]).items():
            entry[k] = kept.get(k, v)
        for k in ("allow", "note"):
            if k in kept:
                entry[k] = kept[k]
    manifest["config"] = current["config"]
    with open(path, "w") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
        f.write("\n")
    return manifest


def stale_entries(manifest: Dict,
                  registered: List[str]) -> List[GcFinding]:
    """GC003 for manifest programs no longer in the registry — the
    contracts file must not accrete dead entries."""
    reg = set(registered)
    return [GcFinding("GC003", name,
                      "manifest entry has no registered program",
                      "remove it from contracts.json (or restore the "
                      "registration)")
            for name in sorted(manifest.get("programs", {}))
            if name not in reg]
