"""graftcheck: compiled-program contract checker (ISSUE 9 tentpole).

Static analysis over the LOWERED artifacts (compiled HLO) of every
jitted entry point registered in
``lightgbm_tpu.utils.jit_registry`` — the IR-level complement to the
AST-level ``tools/graftlint``. See docs/StaticAnalysis.md.

Keep this module import-light: the CLI (``cli.py``) owns the
jax/XLA environment setup; importing the package must not import jax
(graftlint's GL506 front-end and run_report only need the parser and
finding types).
"""

from .checks import check_program, measure
from .findings import GcFinding, RULE_NAMES, sort_findings
from .hlo import census_from_hlo
from .manifest import (MANIFEST_PATH, load_manifest, stale_entries,
                       update_manifest)

__all__ = ["GcFinding", "RULE_NAMES", "sort_findings",
           "check_program", "measure", "census_from_hlo",
           "MANIFEST_PATH", "load_manifest", "update_manifest",
           "stale_entries"]
