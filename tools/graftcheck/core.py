"""graftcheck run loop: build every registered program, measure, and
contract-check against the committed manifest."""

from __future__ import annotations

import traceback
from typing import Dict, List, Optional, Sequence, Tuple

from .checks import check_program, measure
from .findings import GcFinding, sort_findings
from .manifest import load_manifest, stale_entries
from .programs import (BUILDERS, build_program,
                       import_side_registrations)


def run_census(names: Optional[Sequence[str]] = None
               ) -> Tuple[Dict, List[GcFinding]]:
    """Build + measure every requested program. Returns
    ``({"config": ..., "programs": {name: measurements}},
    build_findings)`` — build failures become GC001 findings instead
    of aborting the sweep (one broken program must not hide the other
    29 results)."""
    import jax
    import_side_registrations()
    current: Dict = {
        "config": {
            "backend": jax.default_backend(),
            "devices": jax.device_count(),
            "jax": jax.__version__,
        },
        "programs": {},
    }
    findings: List[GcFinding] = []
    hlo_texts: Dict[str, str] = {}
    for name in sorted(names or BUILDERS):
        try:
            txt = build_program(name)
        except Exception as e:  # noqa: BLE001 — reported as GC001
            findings.append(GcFinding(
                "GC001", name,
                f"failed to build/lower/compile: {type(e).__name__}: "
                f"{e}",
                traceback.format_exc(limit=4)))
            continue
        hlo_texts[name] = txt
        current["programs"][name] = measure(txt)
    current["_hlo"] = hlo_texts  # transient (not written to JSON)
    return current, findings


def check_run(current: Dict, build_findings: List[GcFinding],
              manifest: Optional[Dict] = None) -> List[GcFinding]:
    """Contract-check a run_census result against the manifest."""
    from lightgbm_tpu.utils import jit_registry
    manifest = manifest if manifest is not None else load_manifest()
    findings = list(build_findings)
    progs = manifest.get("programs", {})
    for name, txt in current.get("_hlo", {}).items():
        spec = jit_registry.get(name)
        if spec is None:
            findings.append(GcFinding(
                "GC001", name,
                "example builder exists but no program registered "
                "under this name",
                "register_jit/register_dynamic the site, or drop the "
                "builder"))
            continue
        findings.extend(check_program(spec, txt, progs.get(name)))
    # registry entries with no example builder can never be checked —
    # that is exactly the silent rot GL506 + this sweep exist to stop
    for name in jit_registry.names():
        if name not in BUILDERS:
            findings.append(GcFinding(
                "GC001", name,
                "registered program has no example builder in "
                "tools/graftcheck/programs.py",
                "add a builder so the contract is actually checked"))
    findings.extend(stale_entries(manifest, list(BUILDERS)))
    return sort_findings(findings)
