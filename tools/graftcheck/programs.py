"""Example builders: one per registered program name.

Each builder returns a ``jax`` *Lowered* object for its program at the
FIXED tiny graftcheck config — the checker compiles it and runs the
contract checks over the compiled text. Builders live here (with the
checker), keyed by the names the hot modules register in
``lightgbm_tpu.utils.jit_registry`` — the package carries the
contract, the tool carries the harness.

Shapes are deliberately tiny: every check here is shape-independent
(op lists, alias maps, collective multisets and dtype sets do not
change with row count), so the whole registry compiles in CI time.
Shared fixtures (datasets, trained boosters, learners) are built
lazily ONCE per process in ``_env`` and reused across builders.

The mesh programs shard over every visible device — run with
``--xla_force_host_platform_device_count=8`` (the CLI arranges this
itself; tests inherit conftest's virtual 8-device CPU mesh).
"""

from __future__ import annotations

import warnings
from typing import Callable, Dict, List

# fixed tiny config (grow programs reuse the census tiny shape that
# tests already pin against the committed dispatch budget)
GROW_ROWS, GROW_FEATURES, GROW_LEAVES = 512, 8, 15
N, F, L, C = 256, 8, 16, 4

BUILDERS: Dict[str, Callable] = {}


def builder(name: str):
    def deco(fn):
        BUILDERS[name] = fn
        return fn
    return deco


# ---------------------------------------------------------------------
_ENV: Dict = {}


def _env(key: str, make: Callable):
    if key not in _ENV:
        _ENV[key] = make()
    return _ENV[key]


def _grow_fixture():
    from tools.hlo_census import _build_dataset
    return _build_dataset(GROW_ROWS, GROW_FEATURES, GROW_LEAVES)


def _train_booster(extra_params: Dict, rounds: int = 3, seed: int = 0):
    import numpy as np

    import lightgbm_tpu as lgb
    rng = np.random.RandomState(seed)
    x = rng.randn(N, F).astype(np.float32)
    y = (x[:, 0] - 0.5 * x[:, 1] + 0.2 * rng.randn(N) > 0) \
        .astype(np.float32)
    params = {"objective": "binary", "num_leaves": L - 1,
              "min_data_in_leaf": 5, "verbosity": -1, "seed": seed}
    params.update(extra_params)
    ds = lgb.Dataset(x, label=y, free_raw_data=False)
    return lgb.train(params, ds, num_boost_round=rounds)


def _booster():
    return _env("booster", lambda: _train_booster({}))


def _booster_linear():
    return _env("booster_linear",
                lambda: _train_booster({"linear_tree": True}, rounds=2))


def _best_tree(bst):
    models = bst._gbdt.models
    return max(models, key=lambda t: t.num_leaves)


def _serial_learner():
    def make():
        from lightgbm_tpu.learner.serial import SerialTreeLearner
        ds, cfg = _env("grow_fixture", _grow_fixture)
        return SerialTreeLearner(ds, cfg)
    return _env("serial_learner", make)


def _partitioned_learner():
    def make():
        from lightgbm_tpu.learner.partitioned import \
            PartitionedTreeLearner
        ds, cfg = _env("grow_fixture", _grow_fixture)
        return PartitionedTreeLearner(ds, cfg)
    return _env("partitioned_learner", make)


def _spec_fn(name: str):
    from lightgbm_tpu.utils.jit_registry import get
    spec = get(name)
    if spec is None or spec.fn is None:
        raise RuntimeError(f"program {name!r} is not registered (or "
                           "its dynamic creation path did not run)")
    return spec.fn


# --- gbdt score updaters / bagging -----------------------------------
@builder("score_add_leaf")
def _b_score_add_leaf():
    import jax.numpy as jnp
    fn = _spec_fn("score_add_leaf")
    return fn.lower(jnp.zeros((N, 1), jnp.float32),
                    jnp.zeros((L,), jnp.float32),
                    jnp.zeros((N,), jnp.int32), tid=0)


@builder("score_add_col")
def _b_score_add_col():
    import jax.numpy as jnp
    fn = _spec_fn("score_add_col")
    return fn.lower(jnp.zeros((N, 1), jnp.float32),
                    jnp.zeros((N,), jnp.float32), tid=0)


@builder("score_add_leaf_linear")
def _b_score_add_leaf_linear():
    import jax.numpy as jnp
    fn = _spec_fn("score_add_leaf_linear")
    return fn.lower(jnp.zeros((N, 1), jnp.float32),
                    jnp.zeros((L,), jnp.float32),
                    jnp.zeros((L,), jnp.float32),
                    jnp.zeros((L, C), jnp.float32),
                    jnp.full((L, C), -1, jnp.int32),
                    jnp.zeros((N,), jnp.int32),
                    jnp.zeros((N, F), jnp.float32), tid=0)


@builder("refit_tree")
def _b_refit_tree():
    import jax.numpy as jnp
    fn = _spec_fn("refit_tree")
    return fn.lower(jnp.zeros((N, 1), jnp.float32),
                    jnp.zeros((N,), jnp.int32),
                    jnp.zeros((N,), jnp.float32),
                    jnp.ones((N,), jnp.float32),
                    jnp.zeros((L,), jnp.float32),
                    jnp.float32(0.1), jnp.float32(0.9),
                    nl=L, tid=0, l1=0.0, l2=0.0, mds=20.0)


@builder("refit_tree_linear")
def _b_refit_tree_linear():
    import jax.numpy as jnp
    fn = _spec_fn("refit_tree_linear")
    return fn.lower(jnp.zeros((N, 1), jnp.float32),
                    jnp.zeros((N,), jnp.int32),
                    jnp.zeros((N,), jnp.float32),
                    jnp.ones((N,), jnp.float32),
                    jnp.zeros((N, F), jnp.float32),
                    jnp.full((L, C), -1, jnp.int32),
                    jnp.zeros((L,), jnp.float32),
                    jnp.zeros((L,), jnp.float32),
                    jnp.zeros((L, C), jnp.float32),
                    jnp.float32(0.1), jnp.float32(0.9),
                    nl=L, tid=0, l1=0.0, l2=0.0, mds=20.0,
                    lam=0.01, l2lin=0.0)


@builder("bag_mask")
def _b_bag_mask():
    import jax
    import jax.numpy as jnp
    fn = _spec_fn("bag_mask")
    return fn.lower(jax.random.PRNGKey(0), jnp.int32(0), None,
                    freq=1, n=N, frac=0.8, pos_frac=1.0, neg_frac=1.0)


@builder("gbdt_grad")
def _b_gbdt_grad():
    import jax.numpy as jnp
    bst = _booster()          # registration happens at construction
    return _spec_fn("gbdt_grad").lower(jnp.zeros((N,), jnp.float32))


@builder("gbdt_grad_bag")
def _b_gbdt_grad_bag():
    import jax.numpy as jnp

    def make():
        bst = _train_booster({"bagging_fraction": 0.5,
                              "bagging_freq": 1}, rounds=1, seed=1)
        g = bst._gbdt
        g._grad_hess_bag(g.train_score[:, 0], 0)  # builds the program
        return bst
    _env("booster_bag", make)
    return _spec_fn("gbdt_grad_bag").lower(
        jnp.zeros((N,), jnp.float32), jnp.int32(0))


@builder("gbdt_fused_block")
def _b_gbdt_fused_block():
    import jax.numpy as jnp

    def make():
        import os
        os.environ["LGBM_TPU_FUSE_ITERS"] = "1"
        try:
            bst = _train_booster({"tree_learner": "partitioned"},
                                 rounds=1, seed=2)
            g = bst._gbdt
            assert g._fused_scan_supported(), \
                "fused-scan path not eligible at the fixture config"
            g._train_fused_blocks(0)   # builds _fused_jit, trains 0
            return bst
        finally:
            os.environ.pop("LGBM_TPU_FUSE_ITERS", None)
    bst = _env("booster_fused", make)
    g = bst._gbdt
    ln = g.learner
    return _spec_fn("gbdt_fused_block").lower(
        ln.mat, ln.ws, g.train_score, (), jnp.float32(0.1),
        jnp.int32(g.iter), m=2)


# --- tree traversal / prediction -------------------------------------
@builder("tree_traverse_binned")
def _b_tree_traverse():
    import jax.numpy as jnp
    bst = _booster()
    t = _best_tree(bst)
    binned = bst._gbdt.train_data.binned_device
    return _spec_fn("tree_traverse_binned").lower(
        binned, *t._padded_traversal_args(), mv_slots=None,
        mv_present=False)


@builder("tree_traverse_add")
def _b_tree_traverse_add():
    import jax.numpy as jnp
    bst = _booster()
    t = _best_tree(bst)
    binned = bst._gbdt.train_data.binned_device
    score = jnp.zeros((binned.shape[0], 1), jnp.float32)
    return _spec_fn("tree_traverse_add").lower(
        score, binned, *t._padded_traversal_args(), mv_slots=None,
        tid=0, mv_present=False)


@builder("tree_traverse_linear")
def _b_tree_traverse_linear():
    bst = _booster_linear()
    t = _best_tree(bst)
    ds = bst._gbdt.train_data
    return _spec_fn("tree_traverse_linear").lower(
        ds.binned_device, *t._padded_traversal_args(),
        *t._padded_linear_args(), ds.raw_numeric_device,
        mv_slots=None, mv_present=False)


@builder("tree_traverse_add_linear")
def _b_tree_traverse_add_linear():
    import jax.numpy as jnp
    bst = _booster_linear()
    t = _best_tree(bst)
    ds = bst._gbdt.train_data
    score = jnp.zeros((ds.binned_device.shape[0], 1), jnp.float32)
    return _spec_fn("tree_traverse_add_linear").lower(
        score, ds.binned_device, *t._padded_traversal_args(),
        *t._padded_linear_args(), ds.raw_numeric_device,
        mv_slots=None, tid=0, mv_present=False)


@builder("tree_traverse_arrays")
def _b_tree_traverse_arrays():
    import jax.numpy as jnp
    bst = _booster()
    t = _best_tree(bst)
    arr = t._padded_traversal_args()
    binned = bst._gbdt.train_data.binned_device
    return _spec_fn("tree_traverse_arrays").lower(
        binned, *arr, jnp.int32(t.num_leaves), mv_slots=None,
        mv_present=False)


@builder("predict_scan_trees")
def _b_predict_scan_trees():
    import jax.numpy as jnp
    from lightgbm_tpu.predictor import stack_tree_arrays
    bst = _booster()
    models = list(bst._gbdt.models)
    stacked = _env("stacked", lambda: stack_tree_arrays(models, 1))
    binned = bst._gbdt.train_data.binned_device
    return _spec_fn("predict_scan_trees").lower(
        binned, *stacked.device(), 1, None, False)


@builder("predict_scan_leaf_idx")
def _b_predict_scan_leaf_idx():
    import jax.numpy as jnp
    from lightgbm_tpu.predictor import stack_tree_arrays
    bst = _booster()
    models = list(bst._gbdt.models)
    stacked = _env("stacked", lambda: stack_tree_arrays(models, 1))
    binned = bst._gbdt.train_data.binned_device
    return _spec_fn("predict_scan_leaf_idx").lower(
        binned, *stacked.device(), None, False)


@builder("predict_scan_trees_linear")
def _b_predict_scan_trees_linear():
    import jax.numpy as jnp
    from lightgbm_tpu.predictor import stack_tree_arrays
    bst = _booster_linear()
    models = list(bst._gbdt.models)
    stacked = _env("stacked_linear",
                   lambda: stack_tree_arrays(models, 1))
    ds = bst._gbdt.train_data
    return _spec_fn("predict_scan_trees_linear").lower(
        ds.binned_device, *stacked.device(), *stacked.device_linear(),
        ds.raw_numeric_device, 1, None, False)


# --- objectives / sampling / guards / leaf models --------------------
@builder("xendcg_grad")
def _b_xendcg_grad():
    import jax.numpy as jnp
    nq, q, n = 4, 8, 32
    idx = jnp.arange(nq * q, dtype=jnp.int32).reshape(nq, q)
    return _spec_fn("xendcg_grad").lower(
        jnp.zeros((n,), jnp.float32), jnp.zeros((n,), jnp.float32),
        jnp.where(idx < n, idx, n), idx < n,
        jnp.zeros((nq, q), jnp.float32),
        jnp.full((nq,), q, jnp.int32), num_data=n, weights=None)


@builder("goss_weights")
def _b_goss_weights():
    import jax
    import jax.numpy as jnp
    return _spec_fn("goss_weights").lower(
        jnp.zeros((N, 1), jnp.float32), jnp.ones((N, 1), jnp.float32),
        jax.random.PRNGKey(0), top_rate=0.2, other_rate=0.1)


@builder("finite_ok")
def _b_finite_ok():
    import jax.numpy as jnp
    return _spec_fn("finite_ok").lower(
        jnp.zeros((N,), jnp.float32), jnp.ones((N,), jnp.float32))


@builder("linear_leaf_fit")
def _b_linear_leaf_fit():
    import jax.numpy as jnp
    return _spec_fn("linear_leaf_fit").lower(
        jnp.zeros((N, F), jnp.float32), jnp.zeros((N,), jnp.int32),
        jnp.zeros((N,), jnp.float32), jnp.ones((N,), jnp.float32),
        jnp.ones((N,), jnp.float32), jnp.full((L, C), -1, jnp.int32),
        jnp.zeros((L,), jnp.float32), lam=0.1, l2=0.0)


# --- multiboost: B models' iteration as ONE program ------------------
def _multiboost_batch():
    def make():
        import numpy as np

        import lightgbm_tpu as lgb
        from lightgbm_tpu.multiboost.batch import (BoosterBatch,
                                                   ModelSpec)
        rng = np.random.RandomState(0)
        x = rng.randn(GROW_ROWS, GROW_FEATURES).astype(np.float32)
        y = (x[:, 0] - 0.5 * x[:, 1]
             + 0.2 * rng.randn(GROW_ROWS) > 0).astype(np.float32)
        specs = [ModelSpec(params={
            "objective": "binary", "num_leaves": GROW_LEAVES,
            "min_data_in_leaf": 5, "verbosity": -1,
            "learning_rate": 0.1 + 0.1 * i}) for i in range(3)]
        bb = BoosterBatch(lgb.Dataset(x, label=y), specs,
                          num_boost_round=3)
        return bb.setup()
    return _env("multiboost_batch", make)


@builder("multiboost_grow")
def _b_multiboost_grow():
    """The vmapped grow program at its hot (async) boundary: the
    [B, N] score is donated and the contract pins zero collectives —
    vmap widening a cross-device op along the model axis is exactly
    the regression GC401 catches here (see the bad_multiboost
    fixture)."""
    import jax.numpy as jnp
    bb = _multiboost_batch()
    fn = _spec_fn("multiboost_grow")
    score = jnp.zeros((bb.B, bb.N), jnp.float32)
    return fn.lower(score, jnp.int32(1), bb._attrs, bb._masks,
                    bb._hyp, sync0=False)


@builder("multiboost_score_add")
def _b_multiboost_score_add():
    import jax.numpy as jnp
    fn = _spec_fn("multiboost_score_add")
    B = 3
    return fn.lower(jnp.zeros((B, N), jnp.float32),
                    jnp.zeros((B, L), jnp.float32),
                    jnp.zeros((B, N), jnp.int32))


# --- grow programs (shared with the hlo_census front-end) ------------
@builder("serial_grow")
def _b_serial_grow():
    from tools.hlo_census import lower_serial
    ds, cfg = _env("grow_fixture", _grow_fixture)
    return lower_serial(ds, cfg)


@builder("serial_grow_cegb")
def _b_serial_grow_cegb():
    """The lazy-CEGB configuration of the serial grow program: its
    [N, F] charged matrix is the donated buffer the jit site declares
    — this is the config where GC101 proves the alias materializes."""
    import jax.numpy as jnp

    def make():
        import numpy as np

        from lightgbm_tpu.config import Config
        from lightgbm_tpu.data.dataset import Dataset
        from lightgbm_tpu.learner.serial import SerialTreeLearner
        rng = np.random.RandomState(0)
        x = rng.randn(GROW_ROWS, GROW_FEATURES).astype(np.float32)
        y = (rng.rand(GROW_ROWS) < 0.5).astype(np.float32)
        cfg = Config.from_params({
            "objective": "binary", "num_leaves": GROW_LEAVES,
            "min_data_in_leaf": 20, "verbosity": -1,
            "cegb_penalty_feature_lazy":
                [0.1] * GROW_FEATURES})
        return SerialTreeLearner(Dataset.from_numpy(x, cfg, label=y),
                                 cfg)
    lrn = _env("serial_learner_cegb", make)
    assert lrn._cegb_charged is not None, \
        "fixture config did not enable lazy CEGB"
    n = lrn.dataset.num_data
    from lightgbm_tpu.learner.serial import _grow_jit
    from lightgbm_tpu.learner.split_step import split_fusion_default
    return _grow_jit.lower(
        lrn.binned, jnp.zeros((n,), jnp.float32),
        jnp.ones((n,), jnp.float32), lrn._ones_rows,
        lrn._all_features, lrn.meta, rand_key=None,
        cegb_used0=lrn._cegb_used, cegb_charged0=lrn._cegb_charged,
        params=lrn.params, num_leaves=lrn.num_leaves,
        max_depth=lrn.max_depth, num_bins_max=lrn.num_bins_max,
        hist_method=lrn.hist_method, bundled=lrn.bundled,
        extra_trees=False, ff_bynode=1.0, bynode_count=2,
        forced_plan=(), cache_hists=lrn.cache_hists,
        mv_slots=lrn.mv_slots, mv_groups=lrn.mv_groups,
        has_monotone=lrn.has_monotone,
        split_fusion=split_fusion_default())


@builder("partitioned_grow")
def _b_partitioned_grow():
    from tools.hlo_census import lower_partitioned
    ds, cfg = _env("grow_fixture", _grow_fixture)
    return lower_partitioned(ds, cfg)


# --- pallas kernel wrappers (interpret mode on CPU) ------------------
@builder("hist_segment_raw")
def _b_hist_segment_raw():
    import jax.numpy as jnp
    from lightgbm_tpu.learner.partitioned import HIST_BLK
    lrn = _partitioned_learner()
    mat = lrn.mat
    return _spec_fn("hist_segment_raw").lower(
        mat, jnp.int32(0), jnp.int32(lrn.num_data),
        num_features=lrn.num_groups, num_bins=lrn.num_bins_max,
        blk=HIST_BLK, interpret=True)


@builder("hist_segment_nibble")
def _b_hist_segment_nibble():
    import jax.numpy as jnp
    from lightgbm_tpu.learner.partitioned import HIST_BLK

    def make():
        import numpy as np

        from lightgbm_tpu.config import Config
        from lightgbm_tpu.data.dataset import Dataset
        from lightgbm_tpu.learner.partitioned import \
            PartitionedTreeLearner
        rng = np.random.RandomState(0)
        x = rng.randn(N, F).astype(np.float32)
        y = (rng.rand(N) < 0.5).astype(np.float32)
        cfg = Config.from_params({
            "objective": "binary", "num_leaves": 7, "max_bin": 15,
            "min_data_in_leaf": 5, "verbosity": -1})
        return PartitionedTreeLearner(
            Dataset.from_numpy(x, cfg, label=y), cfg)
    lrn = _env("partitioned_learner_nibble", make)
    from lightgbm_tpu.ops.hist_pallas import MAX_NIBBLE_F
    return _spec_fn("hist_segment_nibble").lower(
        lrn.mat, jnp.int32(0), jnp.int32(lrn.num_data),
        num_features=lrn.num_groups, num_bins=lrn.num_bins_max,
        variant="grouped", nibble_cap=MAX_NIBBLE_F, blk=HIST_BLK,
        interpret=True)


def _partition_args(blk: int):
    import jax.numpy as jnp
    lrn = _partitioned_learner()
    b = lrn.num_bins_max
    lut = jnp.zeros((1, 256), jnp.float32)
    return (lrn.mat, lrn.ws, jnp.int32(0), jnp.int32(lrn.num_data),
            jnp.int32(0), jnp.int32(b // 2), jnp.int32(0),
            jnp.int32(0), jnp.int32(0), jnp.int32(b), jnp.int32(0),
            lut), dict(blk=blk, interpret=True, use_lut_path=False)


@builder("partition_segment")
def _b_partition_segment():
    from lightgbm_tpu.learner.partitioned import PART_BLK
    args, kw = _partition_args(PART_BLK)
    return _spec_fn("partition_segment").lower(*args, **kw)


def _fused_step_state(lrn, si_prefix):
    import jax.numpy as jnp

    from lightgbm_tpu.learner.split_step import make_grow_pack
    from lightgbm_tpu.ops.split_step_pallas import pack_meta_tables
    pack = make_grow_pack(si_prefix, merged=True,
                          has_cat=lrn.params.has_categorical,
                          has_monotone=lrn.has_monotone,
                          big_l=lrn.num_leaves)
    ks = len(pack.sf_fields) + len(pack.si_fields)
    kt = len(pack.tf_fields) + len(pack.ti_fields)
    big_l = lrn.num_leaves
    imeta, fmeta = pack_meta_tables(
        lrn.meta, jnp.ones((lrn.meta.num_bins.shape[0],), bool))
    return (jnp.zeros((ks, big_l), jnp.float32),
            jnp.zeros((kt, big_l - 1), jnp.float32), imeta, fmeta)


@builder("fused_split_step_leaf")
def _b_fused_split_step_leaf():
    import jax.numpy as jnp
    lrn = _serial_learner()
    S, T, imeta, fmeta = _fused_step_state(lrn, ())
    n = lrn.dataset.num_data
    g = lrn.dataset.num_groups
    b = lrn.num_bins_max
    hist = jnp.zeros((lrn.num_leaves, g, b, 3), jnp.float32)
    return _spec_fn("fused_split_step_leaf").lower(
        jnp.int32(1), S, T, jnp.zeros((n,), jnp.int32), hist,
        lrn.binned, jnp.zeros((n, 3), jnp.float32), imeta, fmeta,
        params=lrn.params, si_prefix=(), big_l=lrn.num_leaves,
        max_depth=lrn.max_depth, b=b, bundled=lrn.bundled,
        has_monotone=lrn.has_monotone, hist_method=lrn.hist_method,
        interpret=True)


@builder("fused_split_step_segment")
def _b_fused_split_step_segment():
    import jax.numpy as jnp
    from lightgbm_tpu.learner.partitioned import (HIST_BLK,
                                                  SEG_SI_PREFIX)
    lrn = _partitioned_learner()
    S, T, imeta, fmeta = _fused_step_state(lrn, SEG_SI_PREFIX)
    g = lrn.num_groups
    b = lrn.num_bins_max
    hist = jnp.zeros((lrn.num_leaves, g, b, 3), jnp.float32)
    return _spec_fn("fused_split_step_segment").lower(
        jnp.int32(1), S, T, lrn.mat, lrn.ws, hist, imeta, fmeta,
        params=lrn.params, si_prefix=SEG_SI_PREFIX,
        big_l=lrn.num_leaves, max_depth=lrn.max_depth, b=b, f=g,
        n=lrn.num_data, bundled=lrn.bundled,
        has_monotone=lrn.has_monotone, blk=HIST_BLK, interpret=True)


@builder("split_scan_kernel")
def _b_split_scan_kernel():
    import jax.numpy as jnp
    lrn = _serial_learner()
    meta = lrn.meta
    f = int(meta.num_bins.shape[0])
    b = lrn.num_bins_max
    scal = jnp.zeros((1, 5), jnp.float32)
    imeta = jnp.stack([meta.num_bins, meta.missing, meta.default_bin,
                       meta.monotone], axis=1).astype(jnp.int32)
    fmeta = jnp.stack([meta.penalty,
                       jnp.ones((f,), jnp.float32)], axis=1)
    hist = jnp.zeros((f, b), jnp.float32)
    return _spec_fn("split_scan_kernel").lower(
        scal, imeta, fmeta, hist, hist, hist, params=lrn.params,
        interpret=True)


# --- mesh learners (collective programs; 8-device virtual mesh) ------
def _mesh_einsum_lower(name: str, cls_name: str, env_key: str):
    import jax.numpy as jnp

    def make():
        import lightgbm_tpu.parallel.learners as learners
        ds, cfg = _env("grow_fixture", _grow_fixture)
        return getattr(learners, cls_name)(ds, cfg)
    lrn = _env(env_key, make)
    pf = lrn._fn                     # functools.partial(sharded, ...)
    n_pad = lrn._n_pad
    grad = jnp.zeros((n_pad,), jnp.float32)
    hess = jnp.ones((n_pad,), jnp.float32)
    bag = jnp.ones((n_pad,), jnp.float32)
    fmask = jnp.ones((lrn.dataset.num_features,), bool)
    rkey = jnp.zeros((2, 2), jnp.uint32)
    return pf.func.lower(*pf.args, grad, hess, bag, fmask, rkey,
                         lrn._cegb_arg())


@builder("mesh_data_grow")
def _b_mesh_data_grow():
    return _mesh_einsum_lower("mesh_data_grow",
                              "DataParallelTreeLearner", "mesh_data")


@builder("mesh_feature_grow")
def _b_mesh_feature_grow():
    return _mesh_einsum_lower("mesh_feature_grow",
                              "FeatureParallelTreeLearner",
                              "mesh_feature")


@builder("mesh_voting_grow")
def _b_mesh_voting_grow():
    return _mesh_einsum_lower("mesh_voting_grow",
                              "VotingParallelTreeLearner",
                              "mesh_voting")


@builder("mesh_partitioned_grow")
def _b_mesh_partitioned_grow():
    import jax.numpy as jnp

    def make():
        from lightgbm_tpu.parallel.learners import \
            MeshPartitionedTreeLearner
        ds, cfg = _env("grow_fixture", _grow_fixture)
        return MeshPartitionedTreeLearner(ds, cfg, mode="data")
    lrn = _env("mesh_partitioned", make)
    n_pad = lrn._n_pad
    grad = jnp.zeros((n_pad,), jnp.float32)
    hess = jnp.ones((n_pad,), jnp.float32)
    bag = jnp.ones((n_pad,), jnp.float32)
    fmask = jnp.ones((lrn.num_features,), bool)
    rkey = jnp.zeros((2, 2), jnp.uint32)
    cegb0 = jnp.zeros((lrn.num_features,), bool)
    return _spec_fn("mesh_partitioned_grow").lower(
        lrn.mat, lrn.ws, *lrn._grow_extra, grad, hess, bag, fmask,
        rkey, cegb0)


# ---------------------------------------------------------------------
def registered_names() -> List[str]:
    """Names of every registered/declared program graftcheck covers:
    the static registrations import-time discovery sees, plus the
    dynamic ones whose builders create them on demand."""
    return sorted(BUILDERS)


def build_program(name: str) -> str:
    """Lower + compile one program; returns the compiled HLO text."""
    if name not in BUILDERS:
        raise KeyError(f"no example builder for program {name!r}")
    with warnings.catch_warnings():
        # jax warns when a declared donation is unused at THIS example
        # config (e.g. serial_grow with CEGB off) — that is exactly
        # what the manifest's donation count records, not noise worth
        # failing a CI log grep over
        warnings.filterwarnings(
            "ignore", message=".*[Dd]onat.*", category=UserWarning)
        low = BUILDERS[name]()
        return low.compile().as_text()


def import_side_registrations() -> None:
    """Import every module that registers programs at import time, so
    the registry is fully populated before a check run (dynamic
    programs register inside their builders)."""
    # graftlint: allow[GL601]
    import lightgbm_tpu.models.gbdt      # noqa: F401
    import lightgbm_tpu.models.linear    # noqa: F401
    import lightgbm_tpu.models.tree      # noqa: F401
    import lightgbm_tpu.models.variants  # noqa: F401
    import lightgbm_tpu.multiboost.program       # noqa: F401
    import lightgbm_tpu.objective.rank   # noqa: F401
    import lightgbm_tpu.ops.hist_pallas  # noqa: F401
    import lightgbm_tpu.ops.partition_pallas     # noqa: F401
    import lightgbm_tpu.ops.split_scan_pallas    # noqa: F401
    import lightgbm_tpu.ops.split_step_pallas    # noqa: F401
    import lightgbm_tpu.predictor        # noqa: F401
    import lightgbm_tpu.robustness.guards        # noqa: F401
    # graftlint: allow[GL601]
    from lightgbm_tpu.learner import partitioned, serial  # noqa: F401
