"""Table + JSON reporters for graftcheck findings and measurements."""

from __future__ import annotations

import json
from typing import Dict, List

from .findings import GcFinding, RULE_NAMES


def render_table(findings: List[GcFinding], current: Dict) -> str:
    lines = []
    progs = current.get("programs", {})
    if progs:
        w = max(len(n) for n in progs)
        lines.append(f"{'program':<{w}}  ops  fusions  donation  "
                     "collectives")
        for name in sorted(progs):
            c = progs[name]
            cols = ",".join(f"{k}={v}"
                            for k, v in sorted(c["collectives"].items())) \
                or "-"
            lines.append(f"{name:<{w}}  {c['ops']:>3}  "
                         f"{c['fusions']:>7}  {c['donation']:>8}  "
                         f"{cols}")
    if findings:
        lines.append("")
        lines.append(f"{len(findings)} finding(s):")
        for f in findings:
            rule = f"{f.rule}[{RULE_NAMES.get(f.rule, '?')}]"
            lines.append(f"  {f.program}: {rule} {f.message}")
            for dl in f.detail.splitlines():
                lines.append(f"      {dl}")
    else:
        lines.append("")
        lines.append("graftcheck: all program contracts hold")
    return "\n".join(lines) + "\n"


def render_json(findings: List[GcFinding], current: Dict) -> str:
    payload = {
        "config": current.get("config", {}),
        "programs": current.get("programs", {}),
        "findings": [f.to_json() for f in findings],
        "ok": not findings,
    }
    return json.dumps(payload, indent=2, sort_keys=True) + "\n"
