"""graftcheck CLI: ``python -m tools.graftcheck``.

Exit codes: 0 = every contract holds, 1 = findings, 2 = usage error.

Modes:
  (default)   build + measure + contract-check vs contracts.json
  --update    rewrite the manifest measurements (keeps slack/allow)
  --json F    also write the full artifact (config, measurements,
              findings) — the CI job uploads this
  --programs  comma list to restrict the sweep (default: all)
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

# like tools/hlo_census: always the CPU backend (never dial a TPU
# tunnel from CI), with the virtual 8-device mesh the collective
# census needs and the AVX2 cap this sandbox requires
os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    _flags = (_flags + " --xla_force_host_platform_device_count=8") \
        .strip()
if "xla_cpu_max_isa" not in _flags:
    _flags = (_flags + " --xla_cpu_max_isa=AVX2").strip()
os.environ["XLA_FLAGS"] = _flags


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tools.graftcheck",
        description="compiled-program contract checker over every "
                    "registered jit entry point "
                    "(docs/StaticAnalysis.md)")
    p.add_argument("--check", action="store_true",
                   help="explicit check mode (the default; kept for "
                        "workflow symmetry with tools.hlo_census)")
    p.add_argument("--update", action="store_true",
                   help="rewrite contracts.json measurements "
                        "(preserves slack/allow/note fields)")
    p.add_argument("--json", metavar="PATH",
                   help="write the full JSON artifact")
    p.add_argument("--programs", default=None,
                   help="comma list of program names (default: all)")
    p.add_argument("--format", choices=("table", "json"),
                   default="table")
    return p


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    from .core import check_run, run_census
    from .manifest import load_manifest, update_manifest
    from .programs import BUILDERS
    from .reporters import render_json, render_table

    names = None
    if args.programs:
        names = [n.strip() for n in args.programs.split(",")
                 if n.strip()]
        unknown = [n for n in names if n not in BUILDERS]
        if unknown:
            print(f"graftcheck: unknown program(s): "
                  f"{', '.join(unknown)}", file=sys.stderr)
            return 2

    current, build_findings = run_census(names)

    if args.update:
        if build_findings:
            for f in build_findings:
                print(f"  {f.program}: {f.rule} {f.message}")
            print("graftcheck: refusing to --update with build "
                  "failures", file=sys.stderr)
            return 1
        if names is not None:
            print("partial --update: manifest config block describes "
                  "the LAST full run; re-run without --programs to "
                  "refresh every entry")
        update_manifest({k: v for k, v in current.items()
                         if k != "_hlo"})
        print(f"updated contracts for "
              f"{len(current['programs'])} program(s)")
        return 0

    findings = check_run(current, build_findings, load_manifest())
    report = render_table(findings, current) \
        if args.format == "table" else render_json(findings, current)
    print(report, end="")
    if args.json:
        with open(args.json, "w") as f:
            f.write(render_json(findings, current))
        print(f"wrote {args.json}")
    return 1 if findings else 0
