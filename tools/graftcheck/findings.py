"""graftcheck finding record.

Stable rule-id blocks (docs/StaticAnalysis.md):
  GC0xx  harness      (build/lower failure, manifest drift)
  GC1xx  donation     (declared donation did not materialize)
  GC2xx  dtype        (f64 ops, widening converts)
  GC3xx  host sync    (callbacks / infeed / outfeed in hot programs)
  GC4xx  collectives  (census mismatch vs the committed manifest)
  GC5xx  shapes       (dynamic-shape machinery compiled in)
  GC6xx  budgets      (op / fusion count past manifest + slack)
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List


@dataclasses.dataclass(frozen=True)
class GcFinding:
    rule: str        # e.g. "GC101"
    program: str     # registered program name
    message: str
    detail: str = ""  # evidence: offending HLO lines, counts, diffs

    def to_json(self) -> Dict[str, Any]:
        return {"rule": self.rule, "program": self.program,
                "message": self.message, "detail": self.detail}


RULE_NAMES = {
    "GC001": "build-error",
    "GC002": "missing-contract",
    "GC003": "stale-contract",
    "GC101": "donation-dropped",
    "GC201": "f64-op",
    "GC202": "widening-convert",
    "GC301": "host-callback",
    "GC401": "collective-mismatch",
    "GC501": "dynamic-shape",
    "GC601": "op-budget",
    "GC602": "fusion-budget",
}


def sort_findings(findings: List[GcFinding]) -> List[GcFinding]:
    return sorted(findings, key=lambda f: (f.program, f.rule))
