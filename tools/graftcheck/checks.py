"""Per-program contract checks over one compiled HLO module.

Each check reads the program's registered contract
(``lightgbm_tpu.utils.jit_registry.JitProgram``), the committed
manifest entry (``contracts.json``) and the compiled text, and yields
findings with stable GC rule ids. The manifest's per-program
``allow`` list suppresses individual rules (the inline-allow-list
analog of graftlint's ``# graftlint: allow[...]``), and slack fields
absorb benign drift exactly like ``hlo_census_budget.json``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .findings import GcFinding
from .hlo import (aliased_param_count, collective_census,
                  dynamic_shape_lines, host_callback_lines,
                  module_op_counts, nontrivial_total,
                  wide_dtype_lines, widening_convert_lines)


def _lines_detail(lines, cap: int = 3) -> str:
    shown = [f"L{n}: {t[:160]}" for n, t in lines[:cap]]
    more = len(lines) - len(shown)
    if more > 0:
        shown.append(f"... and {more} more")
    return "\n".join(shown)


def measure(hlo_txt: str) -> Dict:
    """The manifest-facing measurements of one compiled program."""
    ops = module_op_counts(hlo_txt)
    return {
        "ops": nontrivial_total(ops),
        "fusions": ops.get("fusion", 0),
        "collectives": collective_census(hlo_txt),
        "donation": aliased_param_count(hlo_txt),
    }


def check_program(spec, hlo_txt: str,
                  entry: Optional[Dict]) -> List[GcFinding]:
    """Contract-check one program. ``entry`` is the committed manifest
    record (None = program not yet recorded -> GC002 plus every
    contract check that needs no baseline)."""
    name = spec.name
    out: List[GcFinding] = []
    allow = set((entry or {}).get("allow", ()))
    cur = measure(hlo_txt)

    if entry is None:
        out.append(GcFinding(
            "GC002", name,
            "program has no entry in contracts.json",
            "run `python -m tools.graftcheck --update` and commit"))

    # GC1xx: declared donation must materialize in the compiled module
    if spec.declares_donation:
        expected = (entry or {}).get("donation")
        minimum = expected if isinstance(expected, int) else 1
        if cur["donation"] < minimum:
            out.append(GcFinding(
                "GC101", name,
                f"declared donation did not materialize: "
                f"{cur['donation']} aliased parameter(s), expected "
                f">= {minimum} (jit site declares donate="
                f"{spec.donate!r})",
                "XLA drops aliases it cannot honor (shape/dtype "
                "mismatch, buffer still live) without failing — check "
                "the donated arg is returned with identical layout"))

    # GC2xx: dtype discipline
    if not spec.allow_f64:
        wide = wide_dtype_lines(hlo_txt)
        if wide:
            out.append(GcFinding(
                "GC201", name,
                f"{len(wide)} instruction(s) produce 8-byte element "
                "types (f64/s64/u64/c128) in an f32 program",
                _lines_detail(wide)))
        conv = widening_convert_lines(hlo_txt)
        if conv:
            out.append(GcFinding(
                "GC202", name,
                f"{len(conv)} widening convert(s) to the x64 family "
                "(python float / numpy scalar promotion leak)",
                _lines_detail(conv)))

    # GC3xx: host callbacks in hot programs
    if spec.hot:
        cbs = host_callback_lines(hlo_txt)
        if cbs:
            out.append(GcFinding(
                "GC301", name,
                f"{len(cbs)} host callback/transfer op(s) compiled "
                "into a hot program (one host round-trip per "
                "dispatch)",
                _lines_detail(cbs)))

    # GC4xx: collective census
    cols = cur["collectives"]
    expected_cols = (entry or {}).get("collectives", {})
    if not spec.collective:
        if cols:
            out.append(GcFinding(
                "GC401", name,
                "collectives in a program whose contract declares "
                f"none: {cols}",
                "a single-device program gained cross-device traffic"))
    elif entry is not None and cols != expected_cols:
        out.append(GcFinding(
            "GC401", name,
            f"collective census changed: {cols} != committed "
            f"{expected_cols}",
            "an extra all-reduce/all-gather per split is exactly the "
            "cost the voting/pipelined designs exist to avoid; if "
            "intentional, re-run --update and justify in the PR"))

    # GC5xx: dynamic shapes
    dyn = dynamic_shape_lines(hlo_txt)
    if dyn:
        out.append(GcFinding(
            "GC501", name,
            f"{len(dyn)} dynamic-shape op(s) (bounded dynamism / "
            "pad-to-static) compiled in",
            _lines_detail(dyn)))

    # GC6xx: op/fusion budgets (the hlo_census model generalized)
    if entry is not None and "ops" in entry:
        limit = entry["ops"] + entry.get("ops_slack", 0)
        if cur["ops"] > limit:
            out.append(GcFinding(
                "GC601", name,
                f"op count {cur['ops']} exceeds budget "
                f"{entry['ops']} + slack {entry.get('ops_slack', 0)}",
                "more executable ops = more per-dispatch fixed cost; "
                "if intentional, --update and justify"))
    if entry is not None and "fusions" in entry:
        limit = entry["fusions"] + entry.get("fusions_slack", 0)
        if cur["fusions"] > limit:
            out.append(GcFinding(
                "GC602", name,
                f"fusion count {cur['fusions']} exceeds budget "
                f"{entry['fusions']} + slack "
                f"{entry.get('fusions_slack', 0)}",
                "fusion fragmentation — XLA stopped fusing something "
                "it used to"))

    return [f for f in out if f.rule not in allow]
