"""Repo tooling. ``tools.graftlint`` is importable (tests, CI); the
standalone scripts in this directory are still run as plain scripts."""
