"""Round-over-round bench trend gate (ROADMAP item 5).

Parses the committed ``BENCH_r*.json`` series (the driver's round
files: ``{"n", "cmd", "rc", "tail", "parsed"}`` — every JSON result
line in ``tail`` is read, ``parsed`` is the headline), tracks the two
series that are *comparable across rounds*, writes a trend report, and
exits nonzero on a regression:

* ``cpu_fixed_baseline_throughput`` — the ONE pinned steady-state CPU
  configuration (``bench.py:CPU_BASELINE_ID``). Points are compared
  only when their ``baseline_config`` ids match: bumping the config id
  deliberately breaks the chain instead of flagging a bogus
  regression. Lower is worse; a drop of more than ``--threshold``
  (default 20%) between consecutive comparable rounds fails the gate.
* serving ``p99_ms`` — from any result line's ``serving`` block, keyed
  by (backend, buckets, batch_sizes) so only like-for-like serving
  measurements chain. Higher is worse.
* fleet ``p99_ms`` — from any result line's ``fleet`` block (the
  replica-pool soak, serving/fleet.py), keyed by (backend, replicas,
  models, buckets, batch_sizes, qps) so only like-for-like fleet
  soaks chain. Higher is worse.

The legacy headline (``higgs_like_train_throughput``) is REPORTED but
never gated: the r01-r05 history mixes row counts, iteration counts
and backends, which is exactly the noise the fixed baseline exists to
replace.

Stdlib-only on purpose: the CI job runs it without jax.

Usage::

    python tools/bench_trend.py [FILES...] [--threshold 0.2]
                                [--report trend_report.json] [--quiet]

No FILES -> ``BENCH_r*.json`` in the repo root, sorted. Exit codes:
0 = no regression, 1 = regression(s), 2 = no parsable input.
"""

from __future__ import annotations

import glob
import json
import os
import re
import sys
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DEFAULT_THRESHOLD = 0.20

FIXED_METRIC = "cpu_fixed_baseline_throughput"
HEADLINE_METRIC = "higgs_like_train_throughput"
DISPATCH_METRIC = "dispatches_per_split"
MULTIBOOST_METRIC = "multiboost_speedup"


def extract_lines(text: str) -> List[Dict[str, Any]]:
    """Every parsable JSON result line in a blob (same acceptance rule
    as ``bench.find_result_line``, but keeping ALL lines)."""
    out = []
    for line in (text or "").splitlines():
        line = line.strip()
        if line.startswith("{") and '"metric"' in line:
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


def round_label(path: str, data: Dict[str, Any]) -> str:
    m = re.search(r"r(\d+)", os.path.basename(path))
    if m:
        return f"r{int(m.group(1)):02d}"
    n = data.get("n")
    return f"r{int(n):02d}" if isinstance(n, (int, float)) else \
        os.path.basename(path)


def load_round(path: str) -> Optional[Dict[str, Any]]:
    """One round file -> {"label", "path", "lines"} or None."""
    try:
        with open(path) as fh:
            data = json.load(fh)
    except (OSError, ValueError) as e:
        sys.stderr.write(f"bench_trend: skipping {path}: {e}\n")
        return None
    lines = extract_lines(data.get("tail", ""))
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and parsed.get("metric") \
            and parsed not in lines:
        lines.append(parsed)
    return {"label": round_label(path, data), "path": path,
            "lines": lines}


def _fixed_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's fixed-baseline measurement: an explicit
    cpu_fixed_baseline_throughput line, else a headline that reused
    the fixed config as its CPU fallback (source=cpu_fixed_baseline).
    The LAST matching line wins (bench prints escalating attempts).
    The line's per-phase wall-time decomposition (``phases``) rides
    along for regression attribution."""
    found = None
    for ln in lines:
        if ln.get("metric") == FIXED_METRIC \
                or (ln.get("metric") == HEADLINE_METRIC
                    and ln.get("source") == "cpu_fixed_baseline"):
            if ln.get("value") is not None \
                    and ln.get("baseline_config"):
                found = {"value": float(ln["value"]),
                         "key": str(ln["baseline_config"])}
                ph = ln.get("phases")
                if isinstance(ph, dict) and ph:
                    found["phases"] = {str(k): float(v)
                                       for k, v in ph.items()
                                       if isinstance(v, (int, float))}
    return found


def phase_shares(phases: Dict[str, float]) -> Dict[str, float]:
    """Normalize absolute per-phase seconds into shares of the total
    (shares compare across rounds even when the absolute wall time
    moved — which is exactly the regression case)."""
    tot = sum(v for v in phases.values() if v > 0)
    if tot <= 0:
        return {}
    return {k: round(v / tot, 4) for k, v in phases.items() if v >= 0}


def attribute_regression(prev_phases: Dict[str, float],
                         cur_phases: Dict[str, float]
                         ) -> Optional[Dict[str, Any]]:
    """Name the phase whose share of the wall time GREW the most
    between two comparable rounds — when the headline regresses, that
    phase is where the regression lives. Returns None when either
    round lacks a phase decomposition."""
    ps, cs = phase_shares(prev_phases or {}), \
        phase_shares(cur_phases or {})
    if not ps or not cs:
        return None
    deltas = {k: round(cs.get(k, 0.0) - ps.get(k, 0.0), 4)
              for k in set(ps) | set(cs)}
    worst = max(deltas, key=lambda k: deltas[k])
    return {
        "phase": worst,
        "from_share": ps.get(worst, 0.0),
        "to_share": cs.get(worst, 0.0),
        "share_delta": deltas[worst],
        "share_deltas": dict(sorted(deltas.items(),
                                    key=lambda kv: -kv[1])),
    }


def _serving_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's serving p99, keyed by the measurement shape."""
    found = None
    for ln in lines:
        sv = ln.get("serving")
        if not isinstance(sv, dict) or sv.get("p99_ms") is None:
            continue
        key = json.dumps({
            "backend": ln.get("backend"),
            "buckets": sv.get("buckets"),
            "batch_sizes": sv.get("batch_sizes"),
            "mode": sv.get("mode"),
        }, sort_keys=True)
        found = {"value": float(sv["p99_ms"]), "key": key,
                 "p50": sv.get("p50_ms"), "p95": sv.get("p95_ms")}
    return found


def _fleet_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's fleet-soak p99, keyed by the soak shape."""
    found = None
    for ln in lines:
        fv = ln.get("fleet")
        if not isinstance(fv, dict) or fv.get("p99_ms") is None:
            continue
        key = json.dumps({
            "backend": fv.get("backend", ln.get("backend")),
            "replicas": fv.get("replicas"),
            "models": fv.get("models"),
            "buckets": fv.get("buckets"),
            "batch_sizes": fv.get("batch_sizes"),
            "qps": fv.get("offered_qps"),
        }, sort_keys=True)
        found = {"value": float(fv["p99_ms"]), "key": key,
                 "p50": fv.get("p50_ms"),
                 "throughput_rps": fv.get("throughput_rps"),
                 "shed_rate": fv.get("shed_rate"),
                 "availability": fv.get("availability")}
    return found


def _dispatch_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's census-derived dispatches/split (bench.py
    run_dispatch_census): the serial grow program's compiled while-body
    op count on the fixed CPU config — lower is better; keyed by the
    baseline config id so shape bumps break the chain deliberately.
    The value also rides the cpu_fixed_baseline_throughput line."""
    found = None
    for ln in lines:
        v = None
        if ln.get("metric") == DISPATCH_METRIC:
            v = ln.get("value")
        elif ln.get("metric") == FIXED_METRIC:
            v = ln.get("dispatches_per_split")
        if v is not None and ln.get("baseline_config"):
            found = {"value": float(v),
                     "key": str(ln["baseline_config"])}
    return found


def _multiboost_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's multiboost sweep speedup (bench.py
    run_multiboost_sweep → tools/multiboost_dryrun): batched-sweep
    wall time vs the train-in-a-loop foil for the same models, keyed
    by the sweep shape — higher is better. Only ``ok`` runs (all
    models batched, byte-identical, dispatch budget met) chain; a
    failing dryrun trips CI's own exit code and must not seed the
    trend with a broken point."""
    found = None
    for ln in lines:
        if ln.get("metric") != MULTIBOOST_METRIC \
                or ln.get("value") is None or not ln.get("ok"):
            continue
        key = json.dumps({"models": ln.get("models"),
                          "rows": ln.get("rows"),
                          "iters": ln.get("iters")}, sort_keys=True)
        found = {"value": float(ln["value"]), "key": key,
                 "dispatch_ratio": ln.get("dispatch_ratio"),
                 "batched_s": ln.get("batched_s"),
                 "loop_s": ln.get("loop_s")}
    return found


def _fleet_isolation_point(lines: List[Dict]
                           ) -> Optional[Dict[str, Any]]:
    """The round's process-isolation p99 (bench.py
    measure_fleet_isolation): the process-mode fleet soak p99, keyed
    by the measurement shape, with the thread-mode p99 and the
    restart-to-ready latency carried alongside. Higher is worse."""
    found = None
    for ln in lines:
        fi = ln.get("fleet_isolation")
        if not isinstance(fi, dict) or fi.get("process_p99_ms") is None:
            continue
        key = json.dumps({
            "backend": ln.get("backend"),
            "replicas": fi.get("replicas"),
            "buckets": fi.get("buckets"),
            "qps": fi.get("offered_qps"),
        }, sort_keys=True)
        found = {"value": float(fi["process_p99_ms"]), "key": key,
                 "thread_p99_ms": fi.get("thread_p99_ms"),
                 "restart_ready_ms": fi.get("restart_ready_ms"),
                 "process_overhead_pct": fi.get(
                     "process_overhead_pct")}
    return found


def _single_row_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's AOT single-row serving p99 (bench.py
    measure_aot_serving inside the fleet_isolation block): a
    sequential closed loop of 1-row predicts through the process
    fleet's AOT device route — the per-call floor of the zero-Python
    hot path. Higher is worse. The shm/JSON large-batch legs ride
    along for gate-trip leg attribution."""
    found = None
    for ln in lines:
        fi = ln.get("fleet_isolation")
        if not isinstance(fi, dict) \
                or fi.get("single_row_p99_ms") is None:
            continue
        key = json.dumps({
            "backend": ln.get("backend"),
            "buckets": fi.get("buckets"),
        }, sort_keys=True)
        found = {"value": float(fi["single_row_p99_ms"]), "key": key,
                 "aot_p99_ms": fi.get("aot_p99_ms"),
                 "shm_large_batch_p99_ms": fi.get(
                     "shm_large_batch_p99_ms"),
                 "json_large_batch_p99_ms": fi.get(
                     "json_large_batch_p99_ms"),
                 "aot_restart_ready_ms": fi.get(
                     "aot_restart_ready_ms")}
    return found


def _shm_batch_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's shm-transport large-batch p99 (same bench block):
    the batch leg that rides the shared-memory ring instead of JSON
    framing, keyed by the batch shape. Higher is worse."""
    found = None
    for ln in lines:
        fi = ln.get("fleet_isolation")
        if not isinstance(fi, dict) \
                or fi.get("shm_large_batch_p99_ms") is None:
            continue
        key = json.dumps({
            "backend": ln.get("backend"),
            "batch_rows": fi.get("aot_batch_rows"),
        }, sort_keys=True)
        found = {"value": float(fi["shm_large_batch_p99_ms"]),
                 "key": key,
                 "single_row_p99_ms": fi.get("single_row_p99_ms"),
                 "json_large_batch_p99_ms": fi.get(
                     "json_large_batch_p99_ms"),
                 "shm_speedup_pct": fi.get("shm_speedup_pct")}
    return found


def _rel_change(a, b) -> Optional[float]:
    try:
        a, b = float(a), float(b)
    except (TypeError, ValueError):
        return None
    return (b - a) / a if a > 0 else None


def attribute_hot_path_leg(trips: List[Dict[str, Any]],
                           series_name: str,
                           series: List[Tuple[str, Dict]],
                           threshold: float) -> None:
    """Name which leg of the zero-Python hot path a gate trip lives
    in: single rows travel JSON framing but run the AOT executables
    (the ``aot`` leg), large batches additionally ride the shm ring
    (the ``shm`` leg). A trip where BOTH legs worsened past the
    threshold is ``both``; a trip where only the other leg's series
    stayed flat pins the regression to this one."""
    pts = {label: pt for label, pt in series}
    for reg in trips:
        if reg.get("series") != series_name:
            continue
        prev = pts.get(reg["from_round"])
        cur = pts.get(reg["to_round"])
        if not prev or not cur:
            continue
        if series_name == "single_row_p99_ms":
            aot_chg = _rel_change(prev["value"], cur["value"])
            shm_chg = _rel_change(prev.get("shm_large_batch_p99_ms"),
                                  cur.get("shm_large_batch_p99_ms"))
        else:
            shm_chg = _rel_change(prev["value"], cur["value"])
            aot_chg = _rel_change(prev.get("single_row_p99_ms"),
                                  cur.get("single_row_p99_ms"))
        aot_bad = aot_chg is not None and aot_chg > threshold
        shm_bad = shm_chg is not None and shm_chg > threshold
        if aot_bad and shm_bad:
            leg = "both"
        elif aot_bad:
            leg = "aot"
        elif shm_bad:
            leg = "shm"
        else:
            leg = "aot" if series_name == "single_row_p99_ms" \
                else "shm"
        reg["leg"] = leg
        reg["leg_changes"] = {
            "aot_single_row_pct":
                None if aot_chg is None else round(aot_chg * 100, 2),
            "shm_large_batch_pct":
                None if shm_chg is None else round(shm_chg * 100, 2)}


def _mesh_scaling_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's mesh-scaling number (bench.py
    run_mesh_scaling_block): total ms/split across the mesh learner
    modes at the max device count, keyed by (backend, shape id) —
    lower is better. The per-mode curves and scaling efficiencies
    ride along for the report."""
    found = None
    for ln in lines:
        ms = ln.get("mesh_scaling")
        if ln.get("metric") != "mesh_scaling" \
                or not isinstance(ms, dict) \
                or ln.get("value") is None:
            continue
        key = json.dumps({
            "backend": ln.get("backend"),
            "config": ln.get("baseline_config"),
        }, sort_keys=True)
        found = {"value": float(ln["value"]), "key": key,
                 "devices": ms.get("devices"),
                 "modes": ms.get("modes"),
                 "speedup": ms.get("speedup")}
    return found


def _fused_split_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    """The round's fused split-step megakernel per-split wall time
    (bench.py run_fused_split_block), keyed by (backend, shape id) so
    only like-for-like measurements chain — lower is better; a CPU
    point tracks the interpret twin's structural cost, a TPU point the
    compiled megakernel."""
    found = None
    for ln in lines:
        fs = ln.get("fused_split")
        if ln.get("metric") != "fused_split_kernel" \
                or not isinstance(fs, dict) \
                or fs.get("per_split_ms") is None:
            continue
        key = json.dumps({
            "backend": ln.get("backend"),
            "config": ln.get("baseline_config"),
        }, sort_keys=True)
        found = {"value": float(fs["per_split_ms"]), "key": key,
                 "foil_per_split_ms": fs.get("foil_per_split_ms"),
                 "speedup_vs_foil": fs.get("speedup_vs_foil"),
                 "achieved_gbps": fs.get("achieved_gbps")}
    return found


def _headline_point(lines: List[Dict]) -> Optional[Dict[str, Any]]:
    for ln in reversed(lines):
        if ln.get("metric") == HEADLINE_METRIC \
                and ln.get("value") is not None:
            return {"value": float(ln["value"]),
                    "backend": ln.get("backend"),
                    "rows": ln.get("rows")}
    return None


def _gate(series: List[Tuple[str, Dict]], higher_is_better: bool,
          threshold: float, name: str) -> List[Dict[str, Any]]:
    """Consecutive comparable points (equal ``key``) whose worsening
    exceeds the threshold. A regression between two points that both
    carry a ``phases`` decomposition additionally names the phase
    whose span share regressed (``attribution``) — the gate trip says
    *where*, not just *how much*."""
    regressions = []
    prev_label, prev = None, None
    for label, point in series:
        if prev is not None and point["key"] == prev["key"] \
                and prev["value"] > 0:
            change = (point["value"] - prev["value"]) / prev["value"]
            worsening = -change if higher_is_better else change
            if worsening > threshold:
                reg = {
                    "series": name,
                    "from_round": prev_label, "to_round": label,
                    "from_value": prev["value"],
                    "to_value": point["value"],
                    "change_pct": round(change * 100.0, 2),
                    "threshold_pct": round(threshold * 100.0, 2),
                    "key": point["key"],
                }
                attr = attribute_regression(prev.get("phases"),
                                            point.get("phases"))
                if attr is not None:
                    reg["attribution"] = attr
                regressions.append(reg)
        prev_label, prev = label, point
    return regressions


def analyze(rounds: List[Dict[str, Any]],
            threshold: float = DEFAULT_THRESHOLD) -> Dict[str, Any]:
    fixed, serving, headline, dispatch, fleet = [], [], [], [], []
    fused, mesh, fleet_iso = [], [], []
    single_row, shm_batch, mboost = [], [], []
    for rnd in rounds:
        p = _fixed_point(rnd["lines"])
        if p is not None:
            fixed.append((rnd["label"], p))
        p = _serving_point(rnd["lines"])
        if p is not None:
            serving.append((rnd["label"], p))
        p = _headline_point(rnd["lines"])
        if p is not None:
            headline.append((rnd["label"], p))
        p = _dispatch_point(rnd["lines"])
        if p is not None:
            dispatch.append((rnd["label"], p))
        p = _fleet_point(rnd["lines"])
        if p is not None:
            fleet.append((rnd["label"], p))
        p = _fused_split_point(rnd["lines"])
        if p is not None:
            fused.append((rnd["label"], p))
        p = _mesh_scaling_point(rnd["lines"])
        if p is not None:
            mesh.append((rnd["label"], p))
        p = _fleet_isolation_point(rnd["lines"])
        if p is not None:
            fleet_iso.append((rnd["label"], p))
        p = _single_row_point(rnd["lines"])
        if p is not None:
            single_row.append((rnd["label"], p))
        p = _shm_batch_point(rnd["lines"])
        if p is not None:
            shm_batch.append((rnd["label"], p))
        p = _multiboost_point(rnd["lines"])
        if p is not None:
            mboost.append((rnd["label"], p))

    regressions = _gate(fixed, True, threshold,
                        FIXED_METRIC)
    regressions += _gate(serving, False, threshold, "serving_p99_ms")
    regressions += _gate(dispatch, False, threshold, DISPATCH_METRIC)
    regressions += _gate(fleet, False, threshold, "fleet_p99_ms")
    regressions += _gate(fused, False, threshold, "fused_split_ms")
    regressions += _gate(mesh, False, threshold, "mesh_scaling_ms")
    regressions += _gate(fleet_iso, False, threshold,
                         "fleet_isolation_p99_ms")
    sr_trips = _gate(single_row, False, threshold,
                     "single_row_p99_ms")
    attribute_hot_path_leg(sr_trips, "single_row_p99_ms",
                           single_row, threshold)
    shm_trips = _gate(shm_batch, False, threshold,
                      "shm_large_batch_p99_ms")
    attribute_hot_path_leg(shm_trips, "shm_large_batch_p99_ms",
                           shm_batch, threshold)
    regressions += sr_trips + shm_trips
    regressions += _gate(mboost, True, threshold, MULTIBOOST_METRIC)
    return {
        "rounds": [r["label"] for r in rounds],
        "threshold_pct": round(threshold * 100.0, 2),
        # per-round phase-share decomposition of the fixed baseline
        # (informational; the attribution inside a regression entry is
        # the gated use of this data)
        "phase_shares": [
            {"round": lb, "key": pt["key"],
             "shares": phase_shares(pt["phases"])}
            for lb, pt in fixed if pt.get("phases")],
        "series": {
            FIXED_METRIC: [
                {"round": lb, **pt} for lb, pt in fixed],
            "serving_p99_ms": [
                {"round": lb, **pt} for lb, pt in serving],
            "fleet_p99_ms": [
                {"round": lb, **pt} for lb, pt in fleet],
            "fused_split_ms": [
                {"round": lb, **pt} for lb, pt in fused],
            "mesh_scaling_ms": [
                {"round": lb, **pt} for lb, pt in mesh],
            "fleet_isolation_p99_ms": [
                {"round": lb, **pt} for lb, pt in fleet_iso],
            "single_row_p99_ms": [
                {"round": lb, **pt} for lb, pt in single_row],
            "shm_large_batch_p99_ms": [
                {"round": lb, **pt} for lb, pt in shm_batch],
            DISPATCH_METRIC: [
                {"round": lb, **pt} for lb, pt in dispatch],
            MULTIBOOST_METRIC: [
                {"round": lb, **pt} for lb, pt in mboost],
            # informational only — config drifts across rounds
            HEADLINE_METRIC + "_ungated": [
                {"round": lb, **pt} for lb, pt in headline],
        },
        "gated_points": {FIXED_METRIC: len(fixed),
                         "serving_p99_ms": len(serving),
                         "fleet_p99_ms": len(fleet),
                         "fused_split_ms": len(fused),
                         "mesh_scaling_ms": len(mesh),
                         "fleet_isolation_p99_ms": len(fleet_iso),
                         "single_row_p99_ms": len(single_row),
                         "shm_large_batch_p99_ms": len(shm_batch),
                         DISPATCH_METRIC: len(dispatch),
                         MULTIBOOST_METRIC: len(mboost)},
        "regressions": regressions,
        "verdict": "regression" if regressions else "ok",
    }


def render(report: Dict[str, Any]) -> str:
    L = [f"bench trend over rounds: {', '.join(report['rounds'])}",
         f"threshold: {report['threshold_pct']:.0f}%"]
    for name, pts in report["series"].items():
        L.append("")
        gated = "" if not name.endswith("_ungated") else " (not gated)"
        L.append(f"== {name}{gated} ==")
        if not pts:
            L.append("(no measurements in the series yet)")
            continue
        for pt in pts:
            extra = f"  [{pt['key']}]" if "key" in pt else ""
            L.append(f"{pt['round']:>6}  {pt['value']:>12.4f}{extra}")
    if report.get("phase_shares"):
        L.append("")
        L.append("== fixed-baseline phase shares (attribution "
                 "input) ==")
        for row in report["phase_shares"]:
            body = " ".join(
                f"{k}={100 * v:.0f}%" for k, v in sorted(
                    row["shares"].items(), key=lambda kv: -kv[1]))
            L.append(f"{row['round']:>6}  {body}")
    L.append("")
    if report["regressions"]:
        L.append("REGRESSIONS:")
        for r in report["regressions"]:
            L.append(
                f"  {r['series']}: {r['from_round']} -> "
                f"{r['to_round']}: {r['from_value']:.4f} -> "
                f"{r['to_value']:.4f} ({r['change_pct']:+.1f}% vs "
                f"{r['threshold_pct']:.0f}% allowed)")
            attr = r.get("attribution")
            if attr:
                L.append(
                    f"    attributed to phase '{attr['phase']}': "
                    f"span share {100 * attr['from_share']:.1f}% -> "
                    f"{100 * attr['to_share']:.1f}% "
                    f"({100 * attr['share_delta']:+.1f}pp)")
            if r.get("leg"):
                chg = r.get("leg_changes", {})
                L.append(
                    f"    attributed to the {r['leg']} leg "
                    f"(aot single-row "
                    f"{chg.get('aot_single_row_pct')}%, shm "
                    f"large-batch "
                    f"{chg.get('shm_large_batch_pct')}%)")
    else:
        L.append("verdict: ok (no gated regression)")
    return "\n".join(L) + "\n"


def main(argv: List[str]) -> int:
    threshold = DEFAULT_THRESHOLD
    report_path = None
    files: List[str] = []
    quiet = False
    i = 0
    while i < len(argv):
        a = argv[i]
        if a == "--threshold":
            i += 1
            threshold = float(argv[i])
        elif a.startswith("--threshold="):
            threshold = float(a.split("=", 1)[1])
        elif a == "--report":
            i += 1
            report_path = argv[i]
        elif a.startswith("--report="):
            report_path = a.split("=", 1)[1]
        elif a == "--quiet":
            quiet = True
        elif a.startswith("--"):
            sys.stderr.write(__doc__ + f"\nunknown option {a}\n")
            return 2
        else:
            files.append(a)
        i += 1
    if not files:
        files = sorted(glob.glob(os.path.join(REPO, "BENCH_r*.json")))
    if not files:
        sys.stderr.write("bench_trend: no BENCH round files found\n")
        return 2
    rounds = [r for r in (load_round(f) for f in files) if r]
    if not rounds:
        sys.stderr.write("bench_trend: no parsable round files\n")
        return 2
    report = analyze(rounds, threshold)
    if report_path:
        with open(report_path, "w") as fh:
            json.dump(report, fh, indent=1)
            fh.write("\n")
    if not quiet:
        sys.stdout.write(render(report))
    return 1 if report["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
