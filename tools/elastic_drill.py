"""Elastic distributed-training drill (the CI elastic gate).

Two REAL processes per leg — ``jax.distributed.initialize`` over
localhost, gloo CPU collectives — driven end to end through the fault
grammar (robustness/faults.py) and the collective watchdog
(robustness/elastic.py):

1. **reference** — fault-free 2-process run with coordinated
   checkpoints; its tree digest is the golden answer.
2. **kill**      — ``kill_rank@rank=1,iter=3``: rank 1 SIGKILLs itself
   mid-train (an unannounced pod preemption). Rank 0 must NOT hang:
   the watchdog declares ``peer_lost`` within the heartbeat timeout
   and the rank exits within the abort grace window.
3. **resume**    — same machine list again, ``resume=auto``: picks the
   newest full-quorum coordinated checkpoint and trains to completion
   **byte-identical** to the reference.
4. **shrink**    — the same checkpoint dir resumed by ONE process over
   a 2-virtual-device mesh with ``elastic_resume=true``: the N=2 -> M=1
   elastic reshard must also be byte-identical.
5. **guard**     — the shrink WITHOUT ``elastic_resume`` must die with
   the structured world-mismatch error (never a silent wrong-mesh
   resume).
6. **stall**     — ``stall_rank@rank=1,iter=3,ms=60000``: rank 1 wedges
   (alive, heartbeating, not progressing). Both ranks must abort
   classified ``collective_stall`` within the stall timeout.
7. **drop_hb**   — ``drop_heartbeat@rank=1``: rank 1 keeps training but
   goes silent; rank 0 must declare ``peer_lost`` and the abort
   broadcast must take rank 1 down too.

Artifacts land in the workdir (CI uploads it): per-rank telemetry
JSONL traces, per-rank stdout/stderr, ``watchdog_timeline.json`` (the
merged ``elastic`` records) and ``summary.json``.

Usage: python tools/elastic_drill.py [workdir]
"""

import json
import os
import shutil
import socket
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from probe_taxonomy import classify_elastic_failure  # noqa: E402

N_ROUND = 6
KILL_ITER = 3
LEG_TIMEOUT_S = 240

# the training child: rank >= 0 joins the 2-process world; rank == -1
# is the single-process elastic-resume case (2 virtual devices, so the
# mesh programs and padding match the 2-process run bit-for-bit)
CHILD_SRC = """
import json, os, sys, hashlib
rank, port = int(sys.argv[1]), int(sys.argv[2])
ckpt_dir, n_round = sys.argv[3], int(sys.argv[4])
extra = json.loads(sys.argv[5])
os.environ["JAX_PLATFORMS"] = "cpu"
solo = rank < 0
if solo:
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2").strip()
else:
    os.environ["LIGHTGBM_TPU_RANK"] = str(rank)
import numpy as np
from lightgbm_tpu.config import Config
from lightgbm_tpu.parallel import distributed as dist

params = {
    "objective": "regression", "num_leaves": 7, "tree_learner": "data",
    "num_machines": 2, "verbosity": 0, "metric": "",
    "checkpoint_dir": ckpt_dir, "checkpoint_freq": 2,
    # drill-speed watchdog: detection must land in seconds, not the
    # production default minutes
    "elastic_heartbeat_ms": 100.0,
    "elastic_heartbeat_timeout_ms": 2000.0,
    "elastic_stall_timeout_ms": 60000.0,
    "elastic_abort_grace_ms": 1000.0,
    "elastic_barrier_s": 30.0,
}
params.update(extra)
if not solo:
    params["machines"] = "127.0.0.1:%d,127.0.0.1:%d" % (port, port + 1)
cfg = Config.from_params(params)
assert dist.init_distributed(cfg) is (not solo)

rng = np.random.RandomState(0)
X = rng.randn(400, 5).astype(np.float32)
y = (X[:, 0] + 0.5 * X[:, 1]).astype(np.float32)
from lightgbm_tpu import engine
from lightgbm_tpu.basic import Dataset
booster = engine.train(dict(params), Dataset(X, label=y),
                       num_boost_round=n_round, verbose_eval=False)
# hash the model text up to the parameters footer: the tree section is
# identical across legs, while params embed leg-specific paths/ports
text = booster.model_to_string().split("\\nparameters:")[0]
h = hashlib.sha256(text.encode())
print("DIGEST %d %s %d" % (rank, h.hexdigest(), booster.num_trees()),
      flush=True)
"""


def _free_port_pair() -> int:
    for _ in range(32):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        if port % 2 == 0 and port < 64000:
            return port
    return 29612


def _child_env(workdir: str, leg: str, rank: int) -> dict:
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("LGBM_TPU_FAULTS", None)
    env["LGBM_TPU_TELEMETRY"] = os.path.join(
        workdir, f"{leg}_rank{rank}.telemetry.jsonl")
    env["LGBM_TPU_DIST_INIT_ATTEMPTS"] = "4"
    env["LGBM_TPU_DIST_INIT_BACKOFF_S"] = "0.5"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    env["XLA_FLAGS"] = "--xla_cpu_max_isa=AVX2"
    return env


def _run_leg(workdir: str, child: str, leg: str, ckpt_dir: str,
             ranks, extra: dict, n_round: int = N_ROUND):
    """Spawn one child per rank, wait (bounded), persist artifacts.
    Returns [(rank, returncode, stdout, stderr), ...]."""
    port = _free_port_pair()
    procs = [(r, subprocess.Popen(
        [sys.executable, child, str(r), str(port), ckpt_dir,
         str(n_round), json.dumps(extra)],
        env=_child_env(workdir, leg, r), stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)) for r in ranks]
    results = []
    for r, p in procs:
        try:
            out, err = p.communicate(timeout=LEG_TIMEOUT_S)
        except subprocess.TimeoutExpired:
            for _r2, p2 in procs:
                p2.kill()
            raise SystemExit(
                f"FAIL[{leg}]: rank {r} still running after "
                f"{LEG_TIMEOUT_S}s — the watchdog did not bound the "
                "failure (hung rank)")
        for tag, text in (("out", out), ("err", err)):
            with open(os.path.join(workdir,
                                   f"{leg}_rank{r}.{tag}.log"),
                      "w") as fh:
                fh.write(text)
        results.append((r, p.returncode, out, err))
    return results


def _digest(results, leg: str) -> str:
    digests = {}
    for r, rc, out, err in results:
        assert rc == 0, (f"FAIL[{leg}]: rank {r} exited {rc}\n"
                         f"{err[-2000:]}")
        lines = [ln for ln in out.splitlines()
                 if ln.startswith("DIGEST")]
        assert lines, f"FAIL[{leg}]: rank {r} printed no DIGEST"
        _tag, _rank, digest, ntrees = lines[-1].split()
        assert int(ntrees) == N_ROUND, \
            f"FAIL[{leg}]: rank {r} built {ntrees}/{N_ROUND} trees"
        digests[r] = digest
    assert len(set(digests.values())) == 1, \
        f"FAIL[{leg}]: ranks disagree: {digests}"
    return next(iter(digests.values()))


def _assert_classified(results, leg: str, expect_reason: str,
                       surviving_ranks) -> None:
    """Every surviving rank must exit non-zero (bounded, not hung —
    the hang case already failed in _run_leg) with evidence the
    taxonomy classifies as ``expect_reason``."""
    by_rank = {r: (rc, out, err) for r, rc, out, err in results}
    for r in surviving_ranks:
        rc, out, err = by_rank[r]
        assert rc != 0, \
            f"FAIL[{leg}]: rank {r} exited 0 despite the injected fault"
        got = classify_elastic_failure(out + "\n" + err)
        assert got == expect_reason, (
            f"FAIL[{leg}]: rank {r} classified {got!r}, expected "
            f"{expect_reason!r}\n{err[-1500:]}")
        print(f"[{leg}] rank {r}: exit {rc}, classified "
              f"{expect_reason}")


def _collect_timeline(workdir: str) -> list:
    timeline = []
    for name in sorted(os.listdir(workdir)):
        if not name.endswith(".telemetry.jsonl"):
            continue
        with open(os.path.join(workdir, name)) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if rec.get("kind") in ("elastic", "elastic_abort"):
                    timeline.append({"source": name, **rec})
    return timeline


def main() -> int:
    workdir = sys.argv[1] if len(sys.argv) > 1 else "elastic_drill_work"
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir)
    child = os.path.join(workdir, "elastic_child.py")
    with open(child, "w") as fh:
        fh.write(CHILD_SRC)
    summary = {}

    # 1. fault-free reference: the golden digest
    ref_ck = os.path.join(workdir, "ck_ref")
    ref = _digest(_run_leg(workdir, child, "reference", ref_ck,
                           (0, 1), {}), "reference")
    summary["reference"] = {"digest": ref}
    print(f"[reference] 2-process digest {ref[:16]}…")

    # 2. kill drill: rank 1 dies unannounced at iteration 3 (one
    # coordinated checkpoint exists, at iteration 2); rank 0 must
    # abort bounded + classified, never hang
    kill_ck = os.path.join(workdir, "ck_kill")
    res = _run_leg(workdir, child, "kill", kill_ck, (0, 1),
                   {"faults": f"kill_rank@rank=1,iter={KILL_ITER}"})
    killed = {r: rc for r, rc, _o, _e in res}[1]
    assert killed == -9, \
        f"FAIL[kill]: rank 1 exited {killed}, expected SIGKILL (-9)"
    _assert_classified(res, "kill", "peer_lost", (0,))
    summary["kill"] = {"reason": "peer_lost"}
    # freeze the torn-at-iteration-2 state for the shrink legs before
    # the same-list resume writes newer checkpoints into kill_ck
    shrink_ck = os.path.join(workdir, "ck_shrink")
    shutil.copytree(kill_ck, shrink_ck)
    guard_ck = os.path.join(workdir, "ck_guard")
    shutil.copytree(kill_ck, guard_ck)

    # 3. resume=auto on the SAME machine list -> byte-identical
    got = _digest(_run_leg(workdir, child, "resume", kill_ck,
                           (0, 1), {}), "resume")
    assert got == ref, (f"FAIL[resume]: resumed digest {got[:16]}… != "
                        f"reference {ref[:16]}…")
    summary["resume"] = {"digest": got, "identical": True}
    print("[resume] same-list resume is byte-identical")

    # 4. elastic N=2 -> M=1 reshard resume -> still byte-identical
    got = _digest(_run_leg(workdir, child, "shrink", shrink_ck,
                           (-1,), {"elastic_resume": True}), "shrink")
    assert got == ref, (f"FAIL[shrink]: reshard digest {got[:16]}… != "
                        f"reference {ref[:16]}…")
    summary["shrink"] = {"digest": got, "identical": True}
    print("[shrink] 2->1 elastic reshard resume is byte-identical")

    # 5. the same reshard WITHOUT elastic_resume must be a structured
    # refusal naming both worlds, not a silent wrong-mesh resume
    ((_r, rc, _out, err),) = _run_leg(workdir, child, "guard",
                                      guard_ck, (-1,), {})
    assert rc != 0 and "world mismatch" in err, (
        f"FAIL[guard]: expected the structured world-mismatch error, "
        f"got exit {rc}\n{err[-1500:]}")
    summary["guard"] = {"refused": True}
    print("[guard] world-mismatch resume correctly refused")

    # 6. stall drill: rank 1 stays alive + heartbeating but wedges for
    # 60s; both ranks must classify collective_stall within ~2s
    res = _run_leg(workdir, child, "stall",
                   os.path.join(workdir, "ck_stall"), (0, 1),
                   {"faults": f"stall_rank@rank=1,iter={KILL_ITER},"
                              "ms=60000",
                    "elastic_stall_timeout_ms": 2000.0,
                    "elastic_abort_grace_ms": 500.0})
    _assert_classified(res, "stall", "collective_stall", (0, 1))
    summary["stall"] = {"reason": "collective_stall"}

    # 7. silent-rank drill: rank 1 trains on but stops heartbeating;
    # rank 0's peer_lost verdict must reach rank 1 via the abort
    # broadcast (both ranks down, both classified)
    res = _run_leg(workdir, child, "drop_hb",
                   os.path.join(workdir, "ck_drop"), (0, 1),
                   {"faults": "drop_heartbeat@rank=1"}, n_round=500)
    _assert_classified(res, "drop_hb", "peer_lost", (0, 1))
    summary["drop_hb"] = {"reason": "peer_lost"}

    timeline = _collect_timeline(workdir)
    with open(os.path.join(workdir, "watchdog_timeline.json"),
              "w") as fh:
        json.dump(timeline, fh, indent=1)
    aborts = [r for r in timeline if r.get("event") == "abort"]
    assert aborts, "no abort records reached the telemetry timeline"
    with open(os.path.join(workdir, "summary.json"), "w") as fh:
        json.dump(summary, fh, indent=1)
    print(f"PASS: elastic drill ({len(timeline)} timeline records, "
          f"{len(aborts)} classified aborts) — artifacts in {workdir}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
